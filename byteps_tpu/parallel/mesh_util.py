"""Shared mesh construction for the composite-parallelism modules.

One rule, one place: a 2-D (outer, inner) mesh where the *inner* axis is
laid out over the fastest-varying device dimension — on TPU that is the
dimension with neighboring ICI links, which is where every inner axis
wants to live (sp's K/V ring, tp's per-layer all-reduces, pp's
stage-to-stage ppermute are all latency-bound; dp's once-per-step
gradient reduction is not).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P


def make_2d_mesh(devices: Optional[Sequence], n_inner: int,
                 axis_names: Tuple[str, str]) -> Mesh:
    devs = np.asarray(devices if devices is not None else jax.devices())
    if n_inner <= 0 or devs.size % n_inner:
        raise ValueError(
            f"{devs.size} devices not divisible by "
            f"{axis_names[1]}={n_inner}")
    return Mesh(devs.reshape(devs.size // n_inner, n_inner),
                axis_names=axis_names)


def check_params_on_mesh(mesh: Mesh, params, reshard_hint: str) -> None:
    """Guard for GSPMD train-step wrappers: reject params that were never
    mesh-sharded (fresh ``model.init`` output / host arrays would silently
    run replicated on one device) or that live on a *different* mesh.

    Accepts any multi-device placement: jit outputs come back as
    ``GSPMDSharding`` (no ``.mesh`` attribute), so the check is on the
    device set, not the sharding type."""
    leaf = jax.tree.leaves(params)[0]
    sharding = getattr(leaf, "sharding", None)
    lmesh = getattr(sharding, "mesh", None)
    if lmesh is not None and getattr(lmesh, "devices", None) is not None:
        if lmesh != mesh:
            raise ValueError(
                "params are placed on a different mesh than the one this "
                f"train step was built for — re-shard with {reshard_hint}")
        return
    if mesh.size <= 1:
        return
    device_set = getattr(sharding, "device_set", None)
    if device_set is None or len(device_set) <= 1:
        raise ValueError(
            "params are not mesh-sharded (fresh init output or host "
            f"arrays) — place them with {reshard_hint} first")
    if device_set != set(np.asarray(mesh.devices).flat):
        raise ValueError(
            "params are placed on different devices than this train "
            f"step's mesh — re-shard with {reshard_hint}")


def jit_mapped_step(mesh: Mesh, step: Callable, spec_of: Callable,
                    batch_spec, donate: bool = True,
                    axis_names=None) -> Callable:
    """Wrap a ``step(params, opt_state, batch)`` body in shard_map + jit
    with specs derived from the ACTUAL pytrees on first call (optimizer
    states are optax-defined wrappers a static prefix-spec cannot
    describe).  ``spec_of(tree)`` returns the PartitionSpec tree for any
    params-like pytree; the loss output is replicated.

    ``axis_names`` optionally restricts which mesh axes the shard_map
    treats as manual; the rest stay auto — GSPMD propagates their
    shardings through the body and places their collectives (the hybrid
    the (dp, pp, tp) composite uses: schedule pinned by hand over dp/pp,
    tensor parallelism left to the compiler over tp).

    check_vma=True is load-bearing, not hygiene: these steps normalize
    their loss with collectives INSIDE the differentiated region, and
    without varying-manual-axes tracking jax transposes psum
    conservatively (cotangents re-psum'd), inflating every gradient by
    the mesh size.  Forward stays exact — only training drifts.  (Pinned
    by the step-for-step parity tests of pipeline/expert parallelism.)
    """
    cache = {}
    extra = {} if axis_names is None else {"axis_names": axis_names}

    def wrapper(params, opt_state, batch):
        key = (jax.tree.structure(params), jax.tree.structure(opt_state))
        fn = cache.get(key)
        if fn is None:
            p_spec = spec_of(params)
            o_spec = spec_of(opt_state)
            mapped = jax.shard_map(
                step, mesh=mesh,
                in_specs=(p_spec, o_spec, batch_spec),
                out_specs=(p_spec, o_spec, P()),
                check_vma=True,
                **extra,
            )
            fn = cache[key] = jax.jit(
                mapped, donate_argnums=(0, 1) if donate else ())
        return fn(params, opt_state, batch)

    return wrapper
