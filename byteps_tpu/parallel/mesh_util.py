"""Shared mesh construction for the composite-parallelism modules.

One rule, one place: a 2-D (outer, inner) mesh where the *inner* axis is
laid out over the fastest-varying device dimension — on TPU that is the
dimension with neighboring ICI links, which is where every inner axis
wants to live (sp's K/V ring, tp's per-layer all-reduces, pp's
stage-to-stage ppermute are all latency-bound; dp's once-per-step
gradient reduction is not).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_2d_mesh(devices: Optional[Sequence], n_inner: int,
                 axis_names: Tuple[str, str]) -> Mesh:
    devs = np.asarray(devices if devices is not None else jax.devices())
    if n_inner <= 0 or devs.size % n_inner:
        raise ValueError(
            f"{devs.size} devices not divisible by "
            f"{axis_names[1]}={n_inner}")
    return Mesh(devs.reshape(devs.size // n_inner, n_inner),
                axis_names=axis_names)
