"""(fsdp, tp) composite parallelism for the Llama family — the flagship
modern-LLM configuration (BASELINE.json configs[4]: "Llama-3-8B").

The reference scales with data parallelism only (SURVEY.md §2.6); an 8B
model's params + Adam state (~96 GB in f32) outgrow one chip's HBM, so the
TPU rebuild composes two sharding axes GSPMD-natively:

- **tp** (Megatron-style): attention heads and SwiGLU width are column/
  row-parallel within the fastest ICI dimension — per-layer all-reduces
  are latency-bound, so they ride the shortest links;
- **fsdp** (ZeRO-3 by annotation): the *other* large axis of every weight
  is sharded over the fsdp axis, and the batch is sharded over it too
  (fsdp doubles as dp).  XLA streams each layer's parameter all-gather on
  demand and reduce-scatters its gradients — the per-block streamed
  gather that the flat-vector path (`zero.py`, whole-vector gather) trades
  away, here for free from the annotation (the "pick a mesh, annotate,
  let XLA insert collectives" recipe, in contrast to zero.py's hand-pinned
  shard_map schedule).

Optimizer state inherits the param shardings via ``jax.jit(tx.init,
out_shardings=...)`` — persistent memory per device is
``(params + opt state) / (n_fsdp * n_tp)`` for every sharded leaf.
"""

from __future__ import annotations

import re
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models.llama import Llama, LlamaConfig, lm_loss
from .mesh_util import check_params_on_mesh, make_2d_mesh

FSDP_AXIS = "fsdp"
TP_AXIS = "tp"


def make_fsdp_tp_mesh(devices, n_tp: int) -> Mesh:
    """(fsdp, tp) mesh; tp innermost (fastest ICI neighbors)."""
    return make_2d_mesh(devices, n_tp, (FSDP_AXIS, TP_AXIS))


# Sharding rules matched against the flax param path.  Every rule carries
# BOTH axes: tp on the Megatron axis, fsdp on the complementary large axis.
# First match wins.  Unmatched paths fall back in llama_shardings: large
# leaves are fsdp-sharded on their largest divisible axis (so a new
# projection with an unanticipated name never silently replicates
# gigabytes), small ones (RMSNorm scales) replicate.
_RULES = [
    # attention projections: q/k/v kernels are [hidden, heads, head_dim]
    (r"attn/[qkv]/kernel$", P(FSDP_AXIS, TP_AXIS, None)),
    (r"attn/out/kernel$", P(TP_AXIS, None, FSDP_AXIS)),
    # SwiGLU: gate/up column-parallel, down row-parallel
    (r"mlp/(gate|up)/kernel$", P(FSDP_AXIS, TP_AXIS)),
    (r"mlp/down/kernel$", P(TP_AXIS, FSDP_AXIS)),
    # embedding / unembedding: vocab over tp, hidden over fsdp
    (r"wte/embedding$", P(TP_AXIS, FSDP_AXIS)),
    (r"lm_head/kernel$", P(FSDP_AXIS, TP_AXIS)),
    (r"norm/scale$|_norm/scale$|norm_f/scale$", P()),
]


def fsdp_tp_spec_for(path: str) -> P:
    for pat, spec in _RULES:
        if re.search(pat, path):
            return spec
    return P()


def _path_str(key_path) -> str:
    parts = []
    for k in key_path:
        for attr in ("key", "idx", "name"):
            if hasattr(k, attr):
                parts.append(str(getattr(k, attr)))
                break
        else:
            parts.append(str(k))
    return "/".join(parts)


def llama_shardings(mesh: Mesh, params):
    """NamedSharding tree for a Llama param pytree (rule-matched).

    Axes that don't divide their dimension are dropped to replicated for
    that dim — GQA's few KV heads (num_kv_heads < n_tp) fall back to
    replicated KV projections exactly like Megatron's GQA handling, and
    odd vocab sizes degrade gracefully instead of erroring."""
    import numpy as _np

    def spec(key_path, leaf):
        p = fsdp_tp_spec_for(_path_str(key_path))
        if (all(ax is None for ax in p)
                and int(_np.prod(leaf.shape)) > 1 << 16):
            # unmatched large leaf: fsdp-shard the largest divisible axis
            # rather than silently replicating gigabytes per device
            dims = sorted(range(leaf.ndim), key=lambda d: -leaf.shape[d])
            for d in dims:
                if leaf.shape[d] % mesh.shape[FSDP_AXIS] == 0:
                    p = P(*(FSDP_AXIS if i == d else None
                            for i in range(leaf.ndim)))
                    break
        fixed = tuple(
            (ax if ax is None or leaf.shape[d] % mesh.shape[ax] == 0
             else None)
            for d, ax in enumerate(p))
        return NamedSharding(mesh, P(*fixed))
    return jax.tree_util.tree_map_with_path(spec, params)


def shard_llama_params(mesh: Mesh, params):
    return jax.device_put(params, llama_shardings(mesh, params))


def init_llama_params_sharded(mesh: Mesh, cfg: LlamaConfig, rng,
                              sample_ids, attn_fn=None):
    """``model.init`` under jit with sharded out_shardings: every weight is
    born on its (fsdp, tp) placement and the full tree never materializes
    on one device — at 8B the unsharded f32 tree (~32 GB) would OOM a
    single chip before `shard_llama_params` could run."""
    model = Llama(cfg, attn_fn=attn_fn)
    shapes = jax.eval_shape(model.init, rng, sample_ids)
    shardings = llama_shardings(mesh, shapes)
    return jax.jit(model.init, out_shardings=shardings)(rng, sample_ids)


def shard_llama_batch(mesh: Mesh, batch):
    """Batch over fsdp (it doubles as dp), sequence replicated over tp."""
    return jax.device_put(batch, NamedSharding(mesh, P(FSDP_AXIS, None)))


def init_llama_opt_state(tx: optax.GradientTransformation, sharded_params):
    """tx.init with moment buffers pinned to the param shardings (zeros
    carry no data dependence, so propagation alone would replicate them).

    Optimizer-state subtrees that mirror the params (adam mu/nu etc.) nest
    the params' own tree structure, so each state leaf's key path *ends
    with* some param's key path — match structurally on that suffix
    (longest match wins) rather than by (shape, dtype), which silently
    mis-pins when two differently-sharded params share a shape (e.g. a
    square weight when hidden == intermediate).  Leaves matching no param
    path (step counts, scalars) stay replicated."""
    shardings = jax.tree.map(lambda p: p.sharding, sharded_params)
    mesh = jax.tree.leaves(sharded_params)[0].sharding.mesh
    out_sh = llama_opt_shardings(tx, mesh, sharded_params, shardings)
    return jax.jit(tx.init, out_shardings=out_sh)(sharded_params)


def llama_opt_shardings(tx: optax.GradientTransformation, mesh: Mesh,
                        params, param_shardings):
    """Optimizer-state sharding tree via key-path-suffix structural match
    (see :func:`init_llama_opt_state`).  ``params`` may be real arrays or
    ``jax.ShapeDtypeStruct``s — AOT memory analysis uses the latter to
    place 8B-scale state without materializing it."""
    params_with_path = jax.tree_util.tree_flatten_with_path(params)[0]
    sh_leaves = jax.tree.leaves(param_shardings)
    # longest path first so "layers_0/w" beats a bare "w"
    by_path = sorted(
        ((_path_str(kp), p.shape, sh)
         for (kp, p), sh in zip(params_with_path, sh_leaves)),
        key=lambda kv: -len(kv[0]))
    rep = NamedSharding(mesh, P())

    def sharding_for(key_path, leaf):
        path = _path_str(key_path)
        for ppath, pshape, sh in by_path:
            if ((path == ppath or path.endswith("/" + ppath))
                    and leaf.shape == pshape):
                return sh
        return rep

    shapes = jax.eval_shape(tx.init, params)
    return jax.tree_util.tree_map_with_path(sharding_for, shapes)


def make_fsdp_tp_train_step(mesh: Mesh, cfg: LlamaConfig,
                            tx: optax.GradientTransformation,
                            donate: bool = True,
                            attn_fn: Optional[Callable] = None) -> Callable:
    """Jitted ``(params, opt_state, batch) -> (params, opt_state, loss)``.

    Params must be placed by :func:`shard_llama_params`, the batch by
    :func:`shard_llama_batch`, opt_state by :func:`init_llama_opt_state`.
    Every collective — per-layer fsdp parameter gathers, tp activation
    all-reduces, gradient reduce-scatters — is inserted by XLA from the
    shardings; there is no hand-placed psum.
    """
    model = Llama(cfg, attn_fn=attn_fn)

    def step(params, opt_state, batch):
        def loss_fn(p):
            logits = model.apply(p, batch["input_ids"])
            return lm_loss(logits, batch["labels"])

        loss, grads = jax.value_and_grad(loss_fn)(params)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jitted = jax.jit(step, donate_argnums=(0, 1) if donate else ())

    def wrapper(params, opt_state, batch):
        check_params_on_mesh(mesh, params,
                             "shard_llama_params(mesh, params)")
        return jitted(params, opt_state, batch)

    return wrapper
