"""Keras frontend (reference byteps/keras + byteps/_keras, SURVEY.md §2.4).

``DistributedOptimizer`` wraps any Keras 3 optimizer so gradients are
push_pulled before apply (reference _keras/__init__.py:20-84 overrides
get_gradients/_aggregate_gradients); callbacks cover broadcast-on-start,
metric averaging, and LR schedules/warmup.  ``broadcast_global_variables``
here takes a model (TF2 has no global collection).
"""

from __future__ import annotations

from ..core.api import (  # noqa: F401
    init, shutdown, rank, size, local_rank, local_size, declare,
)
from ..tensorflow import (  # noqa: F401
    push_pull, broadcast_variables, Compression, DistributedOptimizer,
)
from . import callbacks  # noqa: F401

__all__ = [
    "init", "shutdown", "rank", "size", "local_rank", "local_size",
    "declare", "push_pull", "broadcast_variables", "Compression",
    "DistributedOptimizer", "callbacks", "broadcast_global_variables",
    "load_model",
]


def broadcast_global_variables(model, root_rank: int = 0):
    """Broadcast a model's (and its optimizer's) variables from root
    (reference keras/__init__.py broadcast_global_variables, adapted to
    TF2's model-scoped variables)."""
    broadcast_variables(model.variables, root_rank)
    opt = getattr(model, "optimizer", None)
    if opt is not None:
        opt_vars = getattr(opt, "variables", None)
        if callable(opt_vars):
            opt_vars = opt_vars()
        if opt_vars:
            broadcast_variables(opt_vars, root_rank)


def load_model(filepath, custom_optimizers=None, custom_objects=None,
               compression=Compression.none):
    """Load a Keras model, re-wrapping its optimizer in
    ``DistributedOptimizer`` (reference keras/__init__.py load_model)."""
    import keras
    from ..tensorflow import distributed_optimizer_custom_objects

    objs = distributed_optimizer_custom_objects(compression)
    if custom_objects:
        objs.update(custom_objects)
    if custom_optimizers:
        for cls in custom_optimizers:
            from ..tensorflow import _make_distributed_keras_class
            wrapped = _make_distributed_keras_class(cls, compression)
            objs[wrapped.__name__] = wrapped
    model = keras.models.load_model(filepath, custom_objects=objs)
    opt = getattr(model, "optimizer", None)
    if opt is not None and not type(opt).__name__.startswith("Distributed"):
        # class-swap instead of DistributedOptimizer(): a from_config
        # rebuild would discard the optimizer state restored from the file
        # (slot variables, iteration counter)
        from ..tensorflow import _make_distributed_keras_class
        opt.__class__ = _make_distributed_keras_class(
            opt.__class__, compression)
    return model
