"""Keras callbacks (reference byteps/_keras/callbacks.py, SURVEY.md §2.4).

The reference ships four callbacks shared by its keras/tf.keras frontends:
broadcast-on-start, cross-worker metric averaging, an LR multiplier
schedule, and LR warmup.  Same surface here against Keras 3; the averaging
runs through the byteps_tpu engine instead of a TF push_pull op.
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Union

import numpy as np
import keras

from ..core import api as _api


class BroadcastGlobalVariablesCallback(keras.callbacks.Callback):
    """Broadcast model + optimizer variables from ``root_rank`` at the start
    of training (reference _keras/callbacks.py:23-49: fires on the first
    batch end so optimizer slots already exist)."""

    def __init__(self, root_rank: int = 0, device: str = ""):
        super().__init__()
        self.root_rank = root_rank
        self.broadcast_done = False

    def on_batch_end(self, batch, logs=None):
        if self.broadcast_done:
            return
        from . import broadcast_global_variables
        broadcast_global_variables(self.model, self.root_rank)
        self.broadcast_done = True


class MetricAverageCallback(keras.callbacks.Callback):
    """Average epoch-end metrics over all workers (reference
    _keras/callbacks.py:51-91) so rank-0's logged metrics reflect the whole
    job, not its local shard."""

    def __init__(self, device: str = ""):
        super().__init__()

    def _average(self, value: float, name: str) -> float:
        eng = _api._require()
        out = eng.push_pull_local(np.asarray([value], dtype=np.float32),
                                  f"byteps_metric.{name}", op="average")
        return float(np.asarray(out)[0])

    def on_epoch_end(self, epoch, logs=None):
        if logs:
            for name in list(logs):
                try:
                    logs[name] = self._average(float(logs[name]), name)
                except (TypeError, ValueError):
                    pass  # non-scalar entries stay local


class LearningRateScheduleCallback(keras.callbacks.Callback):
    """Multiply the initial LR by ``multiplier(epoch)`` over
    [start_epoch, end_epoch) (reference _keras/callbacks.py:93-174).
    ``staircase=True`` adjusts once per epoch; ``False`` interpolates per
    batch using ``steps_per_epoch``."""

    def __init__(self, multiplier: Union[float, Callable[[float], float]],
                 start_epoch: int = 0, end_epoch: Optional[int] = None,
                 staircase: bool = True,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 initial_lr: Optional[float] = None):
        super().__init__()
        self.start_epoch = start_epoch
        self.end_epoch = end_epoch
        self.staircase = staircase
        self.momentum_correction = momentum_correction
        self.initial_lr = initial_lr
        self.steps_per_epoch = steps_per_epoch
        self.current_epoch = 0
        self.restore_momentum = None
        if callable(multiplier):
            self.staircase = staircase
            self.multiplier = multiplier
        else:
            self.multiplier = lambda epoch: multiplier

    # -- helpers --------------------------------------------------------
    def _lr_var(self):
        return self.model.optimizer.learning_rate

    def _set_lr(self, lr: float):
        opt = self.model.optimizer
        try:
            opt.learning_rate.assign(lr)
        except AttributeError:
            opt.learning_rate = lr

    def _in_window(self, epoch: float) -> bool:
        if epoch < self.start_epoch:
            return False
        return self.end_epoch is None or epoch < self.end_epoch

    def _adjust(self, epoch: float):
        if not self._in_window(epoch):
            return
        lr = self.initial_lr * self.multiplier(epoch)
        # momentum correction (reference _keras/callbacks.py:129-143):
        # when LR jumps, scale momentum by new_lr/old_lr for one step so the
        # accumulated velocity keeps its effective magnitude
        opt = self.model.optimizer
        mom = getattr(opt, "momentum", None)
        old_lr = float(np.asarray(keras.ops.convert_to_numpy(
            self._lr_var())))
        if (self.momentum_correction and mom is not None
                and not callable(mom) and old_lr > 0 and lr != old_lr):
            self.restore_momentum = float(mom)
            opt.momentum = float(mom) * lr / old_lr
        self._set_lr(lr)

    def _restore_momentum_if_needed(self):
        if self.restore_momentum is not None:
            self.model.optimizer.momentum = self.restore_momentum
            self.restore_momentum = None

    # -- keras hooks ----------------------------------------------------
    def on_train_begin(self, logs=None):
        if self.initial_lr is None:
            self.initial_lr = float(np.asarray(
                keras.ops.convert_to_numpy(self._lr_var())))
        if not self.staircase and not self.steps_per_epoch:
            self.steps_per_epoch = self.params.get("steps")
            if not self.steps_per_epoch:
                raise ValueError(
                    "steps_per_epoch is required for smooth (staircase="
                    "False) schedules when Keras cannot infer it")

    def on_epoch_begin(self, epoch, logs=None):
        self.current_epoch = epoch
        if self.staircase:
            self._adjust(epoch)

    def on_batch_begin(self, batch, logs=None):
        if not self.staircase:
            self._adjust(self.current_epoch + float(batch) /
                         self.steps_per_epoch)

    def on_batch_end(self, batch, logs=None):
        self._restore_momentum_if_needed()

    def on_epoch_end(self, epoch, logs=None):
        if logs is not None:
            logs["lr"] = float(np.asarray(
                keras.ops.convert_to_numpy(self._lr_var())))


class LearningRateWarmupCallback(LearningRateScheduleCallback):
    """Gradual LR ramp from ``initial_lr`` to ``initial_lr * size()`` over
    the first ``warmup_epochs`` (reference _keras/callbacks.py:176-196,
    after Goyal et al. "Accurate, Large Minibatch SGD")."""

    def __init__(self, warmup_epochs: int = 5,
                 momentum_correction: bool = True,
                 steps_per_epoch: Optional[int] = None,
                 verbose: int = 0, initial_lr: Optional[float] = None):
        self.warmup_epochs = warmup_epochs
        self.verbose = verbose

        def multiplier(epoch):
            # epoch=0 -> 1/size ... epoch=warmup -> 1.0, then scaled by the
            # size() factor the user bakes into initial_lr
            size = _api.size()
            return 1.0 / size + epoch * (1.0 - 1.0 / size) / warmup_epochs

        super().__init__(multiplier=multiplier, start_epoch=0,
                         end_epoch=warmup_epochs, staircase=False,
                         momentum_correction=momentum_correction,
                         steps_per_epoch=steps_per_epoch,
                         initial_lr=initial_lr)

    def on_epoch_end(self, epoch, logs=None):
        super().on_epoch_end(epoch, logs)
        if epoch == self.warmup_epochs - 1 and self.verbose and \
                _api.rank() == 0:
            print(f"Epoch {epoch + 1}: finished gradual learning rate "
                  f"warmup to {self.initial_lr}.")
